"""Speculative decoding: draft-model proposals verified by the target
in one chunked forward — lossless for greedy decoding (the output is
PROVABLY the target's own greedy sequence; tests assert token
equality), with the target's sequential decode steps replaced by one
``decode_chunk`` per accepted run.

TPU-first mechanics:
- the whole draft→verify→accept loop runs inside ONE ``lax.while_loop``
  under jit — no host round-trips between rounds;
- full-length caches (slot == position) make acceptance rollback-free:
  entries written for rejected candidates sit at positions the next
  round rewrites before anything attends them (``decode_chunk``
  docstring has the invariant);
- per-row positions/acceptance are vectors, so a batch of rows at
  different depths shares the compiled program (same ragged philosophy
  as the continuous engine).

The reference orchestrator has no serving math at all (SURVEY.md §2);
the algorithm is the standard greedy speculative scheme (Leviathan et
al. / Chen et al., public), implemented against this repo's own cache
contracts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def generate_speculative(
    cfg,
    params,
    draft_cfg,
    draft_params,
    prompt: jax.Array,  # [B, P] int32
    *,
    max_new_tokens: int,
    k: int = 4,
    family=None,
    draft_family=None,
    return_rounds: bool = False,
):
    """Greedy generation of ``max_new_tokens`` per row, draft-accelerated.

    Returns [B, max_new_tokens] int32 — bit-identical to
    ``family.generate(..., temperature=0)``. ``k`` = draft tokens per
    round; each round emits between 1 (no proposals accepted: the
    target's own token) and k+1 (all accepted + bonus) tokens.
    ``return_rounds``: also return the number of verify rounds (the
    efficiency observable — self-draft at high acceptance needs
    ~max_new/(k+1) rounds).

    Rows that finish early still ride along until the deepest row is
    done — the same cost shape as the plain path's fixed-length
    ``lax.scan``, not an added inefficiency.
    """
    from polyaxon_tpu.models import llama

    family = family or llama
    draft_family = draft_family or llama
    B, P = prompt.shape
    max_new = int(max_new_tokens)
    # Full-length caches with verify headroom: positions reach at most
    # P + max_new + k.
    max_len = P + max_new + k + 1
    if max_len > cfg.max_seq_len or max_len > draft_cfg.max_seq_len:
        raise ValueError(
            f"prompt {P} + max_new {max_new} + draft window {k}+1 "
            f"exceeds max_seq_len (target {cfg.max_seq_len}, draft "
            f"{draft_cfg.max_seq_len})")

    logits_t, cache_t = family.prefill(cfg, params, prompt, max_len)
    t0 = jnp.argmax(logits_t, axis=-1).astype(jnp.int32)  # token @ pos P
    _, cache_d = draft_family.prefill(draft_cfg, draft_params, prompt,
                                      max_len)

    rows = jnp.arange(B)
    width = max_new + k + 2  # + trash column for masked writes
    trash = width - 1
    out = jnp.zeros((B, width), jnp.int32).at[:, 0].set(t0)
    n0 = jnp.ones((B,), jnp.int32)  # t0 already emitted
    pos0 = jnp.full((B,), P, jnp.int32)  # cur sits at position P

    def cond(state):
        return jnp.any(state[1] < max_new)

    def body(state):
        out, n, cur, pos, cache_t, cache_d, rounds = state
        live = n < max_new

        def draft_step(carry, _):
            cache_d, tok, p = carry
            lg, cache_d = draft_family.decode_step_ragged(
                draft_cfg, draft_params, cache_d, tok, p)
            nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
            return (cache_d, nxt, p + 1), nxt

        # k+1 steps for k proposals: the extra step writes the LAST
        # proposal's draft KV (position pos+k). Without it, a fully-
        # accepted round leaves a permanent zero-KV hole there that
        # every later draft query attends — output stays lossless (the
        # target verifies) but acceptance silently collapses.
        (cache_d, _, _), d = jax.lax.scan(
            draft_step, (cache_d, cur, pos), None, length=k + 1)
        d = d.T[:, :k]  # [B, k] proposals for positions pos+1..pos+k

        chunk = jnp.concatenate([cur[:, None], d], axis=1)  # [B, k+1]
        logits, cache_t = family.decode_chunk(cfg, params, cache_t,
                                              chunk, pos)
        t = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, k+1]
        # Leading proposals the target agrees with; emit those plus the
        # target's own token at the first disagreement (the "bonus").
        match = (d == t[:, :k]).astype(jnp.int32)
        a = jnp.cumprod(match, axis=1).sum(axis=1)  # [B] in 0..k
        emit = jnp.minimum(a + 1, max_new - n)  # capped at the budget
        emit = jnp.where(live, emit, 0)

        idx = jnp.arange(k + 1)[None, :]
        col = jnp.where(idx < emit[:, None], n[:, None] + idx, trash)
        out = out.at[rows[:, None], col].set(t)
        cur = jnp.where(live, t[rows, jnp.maximum(emit - 1, 0)], cur)
        n = n + emit
        pos = pos + emit
        return out, n, cur, pos, cache_t, cache_d, rounds + 1

    out, _, _, _, _, _, rounds = jax.lax.while_loop(
        cond, body,
        (out, n0, t0, pos0, cache_t, cache_d, jnp.int32(0)))
    if return_rounds:
        return out[:, :max_new], rounds
    return out[:, :max_new]
