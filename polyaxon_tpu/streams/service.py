"""Streams: serve logs/events/artifacts per run (SURVEY.md §2 "Streams",
§3.5 read path [K]).

The reference runs this as a FastAPI service multiplexing from fsspec
stores; here it is an embedded service over the store tree that the CLI
and tuner consume directly (the process boundary is optional — the same
class would back an HTTP layer). Supports snapshot reads and follow-mode
tailing with offsets.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Iterator, Optional

from polyaxon_tpu.tracking.events import list_event_names, read_events, tail_file


class StreamsService:
    def __init__(self, store_root: str):
        self.store_root = store_root
        # TTL cache for tree-walk results (dir sizes, detail listings):
        # the dashboard polls every ~5s per viewer, and re-walking a
        # thousand-file run tree per poll is continuous I/O for numbers
        # that change slowly. Expired entries are purged on insert so a
        # long-lived server doesn't accumulate keys for deleted runs.
        # Locked: the API's ThreadingHTTPServer calls this from
        # concurrent handler threads.
        self._walk_cache: dict[Any, tuple[float, Any]] = {}
        self._walk_cache_lock = threading.Lock()
        self._walk_inflight: dict[Any, threading.Event] = {}
        # Per-key insert generation: a walker that was degraded-past
        # (waiters gave up on it and cached their own fresher walk)
        # must not overwrite that newer entry when it finally finishes.
        self._walk_gen: dict[Any, int] = {}

    def _cached_walk(self, key: Any, compute, ttl: float = 10.0):
        with self._walk_cache_lock:
            hit = self._walk_cache.get(key)
            if hit and hit[0] > time.monotonic():
                return hit[1]
            # Single-flight per key: when a TTL lapses with N viewers
            # polling, one thread walks and the rest wait for its
            # result instead of N simultaneous tree walks.
            waiting = self._walk_inflight.get(key)
            if waiting is None:
                self._walk_inflight[key] = threading.Event()
                gen0 = self._walk_gen.get(key, 0)
        if waiting is not None:
            waiting.wait(timeout=30)
            with self._walk_cache_lock:
                hit = self._walk_cache.get(key)
                walker_stuck = self._walk_inflight.get(key) is waiting
            if hit:  # possibly expired, still the freshest walk we have
                return hit[1]
            if walker_stuck:
                # The walker is still running after 30s (hung FS?):
                # degrade to an own walk — bounded latency beats
                # waiting (or recursing) behind it forever — and CACHE
                # the result so pollers arriving during the hang get a
                # hit instead of each launching another walk against
                # the already-slow store. Same generation discipline as
                # the walker path: anything inserted while THIS compute
                # ran started later (so is fresher) — don't clobber it.
                with self._walk_cache_lock:
                    my_gen = self._walk_gen.get(key, 0)
                value = compute()
                done = time.monotonic()
                with self._walk_cache_lock:
                    if self._walk_gen.get(key, 0) == my_gen:
                        self._walk_cache[key] = (done + ttl, value)
                        self._walk_gen[key] = my_gen + 1
                return value
            # Walker finished-with-failure or died: re-enter ONCE —
            # the inflight entry is gone, so one waiter becomes the
            # new walker (and caches); the rest wait on it.
            return self._cached_walk(key, compute, ttl)
        try:
            value = compute()  # the walk itself runs unlocked
            done = time.monotonic()  # expiry from walk END: a walk
            # slower than the TTL must not insert already-expired
            with self._walk_cache_lock:
                for k in [k for k, (exp, _) in self._walk_cache.items()
                          if exp <= done]:
                    del self._walk_cache[k]
                # Generations only matter while a walk is inflight for
                # the key; drop the rest so deleted runs don't pin them.
                for k in [k for k in self._walk_gen
                          if k not in self._walk_cache
                          and k not in self._walk_inflight]:
                    del self._walk_gen[k]
                if self._walk_gen.get(key, 0) == gen0:
                    # No degraded waiter inserted while this walk ran;
                    # otherwise their walk STARTED later (after the 30s
                    # wait) — keep the newer result, drop this one.
                    self._walk_cache[key] = (done + ttl, value)
                    self._walk_gen[key] = gen0 + 1
            return value
        finally:
            # Cache insert happens BEFORE the event fires (walker
            # success path), so woken waiters find the fresh entry; on
            # a compute() exception they re-enter and one becomes the
            # new walker.
            with self._walk_cache_lock:
                event = self._walk_inflight.pop(key, None)
            if event is not None:
                event.set()

    def run_dir(self, run_uuid: str) -> str:
        return os.path.join(self.store_root, run_uuid)

    # -- metrics ----------------------------------------------------------
    def metric_names(self, run_uuid: str) -> list[str]:
        return list_event_names(self.run_dir(run_uuid), "metric")

    def get_metrics(
        self, run_uuid: str, names: Optional[list[str]] = None,
        since_step: Optional[int] = None,
    ) -> dict[str, list[dict[str, Any]]]:
        rd = self.run_dir(run_uuid)
        names = names or self.metric_names(run_uuid)
        return {name: read_events(rd, "metric", name, since_step=since_step)
                for name in names}

    def last_metric(self, run_uuid: str, name: str) -> Optional[float]:
        events = read_events(self.run_dir(run_uuid), "metric", name)
        return events[-1]["value"] if events else None

    def get_events(self, run_uuid: str, kind: str,
                   names: Optional[list[str]] = None) -> dict[str, list[dict]]:
        from polyaxon_tpu.tracking.events import V1EventKind

        if kind not in V1EventKind.VALUES:
            raise ValueError(
                f"unknown event kind `{kind}`; one of {sorted(V1EventKind.VALUES)}")
        rd = self.run_dir(run_uuid)
        # Traversal in user-supplied names is rejected inside read_events
        # (tracking.events.safe_subpath) — the guard covers metrics too.
        names = names or list_event_names(rd, kind)
        return {name: read_events(rd, kind, name) for name in names}

    def get_lineage(self, run_uuid: str) -> list[dict]:
        """Artifact-lineage records appended by tracking.log_artifact /
        log_model (upstream's artifact-lineage API surface), enriched
        with ``rel_path`` (run-dir-relative, usable against the
        artifacts download route) and ``size_bytes`` when the recorded
        path still exists under the run tree — the fields the
        dashboard's artifact browser lists."""
        from polyaxon_tpu.tracking.events import read_jsonl

        root = os.path.abspath(self.run_dir(run_uuid))
        records = read_jsonl(os.path.join(root, "lineage.jsonl"))
        for rec in records:
            path = os.path.abspath(str(rec.get("path", "")))
            if not path.startswith(root + os.sep):
                continue  # registered without copy: outside the run tree
            if not os.path.exists(path):
                continue  # deleted/not-yet-synced: no dead links
            rec["rel_path"] = os.path.relpath(path, root).replace(os.sep, "/")
            rec["is_dir"] = os.path.isdir(path)
            try:
                rec["size_bytes"] = (self._dir_size(path) if rec["is_dir"]
                                     else os.path.getsize(path))
            except OSError:
                pass
        return records

    def _dir_size(self, path: str) -> int:
        """Recursive size of a directory artifact (TTL-cached)."""
        def compute() -> int:
            total = 0
            for dirpath, _, filenames in os.walk(path):
                for name in filenames:
                    try:
                        total += os.path.getsize(os.path.join(dirpath, name))
                    except OSError:
                        pass  # vanished mid-walk
            return total

        return self._cached_walk(("dir_size", path), compute)

    def list_artifacts_detail(self, run_uuid: str,
                              prefix: str = "") -> list[dict]:
        """File listing with sizes, for the dashboard browser. One walk
        with scandir-cached stats (not list_artifacts + a getsize per
        file — that stats the whole tree twice), TTL-cached against the
        dashboard's live-rerender polling."""
        run_root = self.run_dir(run_uuid)
        root = os.path.join(run_root, prefix)
        if not os.path.isdir(root):
            return []

        def compute() -> list[dict]:
            out = []
            for dirpath, _, _ in os.walk(root):
                try:
                    entries = list(os.scandir(dirpath))
                except OSError:
                    continue  # vanished mid-walk
                for entry in entries:
                    try:
                        if not entry.is_file():
                            continue
                        rel = os.path.relpath(entry.path, run_root)
                        out.append({"path": rel.replace(os.sep, "/"),
                                    "size_bytes": entry.stat().st_size})
                    except OSError:
                        continue
            out.sort(key=lambda rec: rec["path"])
            return out

        return self._cached_walk(("detail", run_uuid, prefix), compute,
                                 ttl=5.0)

    # -- logs -------------------------------------------------------------
    def log_files(self, run_uuid: str) -> list[str]:
        root = os.path.join(self.run_dir(run_uuid), "logs")
        if not os.path.isdir(root):
            return []
        return sorted(os.listdir(root))

    def read_logs(self, run_uuid: str, name: str = "main.log", offset: int = 0) -> tuple[str, int]:
        from polyaxon_tpu.tracking.events import safe_subpath

        root = os.path.join(self.run_dir(run_uuid), "logs")
        return tail_file(safe_subpath(root, name), offset)

    def follow_logs(
        self, run_uuid: str, name: str = "main.log", *,
        poll_seconds: float = 1.0, should_stop=None, offset: int = 0,
    ) -> Iterator[str]:
        """SSE-style tail loop (SURVEY §3.5 🔥): yields chunks until
        ``should_stop()`` returns True and the file stops growing.
        ``offset`` resumes after a snapshot read (avoids re-yielding it)."""
        while True:
            chunk, offset = self.read_logs(run_uuid, name, offset)
            if chunk:
                yield chunk
            elif should_stop is not None and should_stop():
                final, offset = self.read_logs(run_uuid, name, offset)
                if final:
                    yield final
                return
            else:
                time.sleep(poll_seconds)

    # -- outputs / statuses / artifacts -----------------------------------
    def get_outputs(self, run_uuid: str) -> dict[str, Any]:
        path = os.path.join(self.run_dir(run_uuid), "outputs.json")
        if not os.path.exists(path):
            return {}
        with open(path) as fh:
            return json.load(fh)

    def get_statuses(self, run_uuid: str) -> list[dict[str, Any]]:
        path = os.path.join(self.run_dir(run_uuid), "statuses.jsonl")
        if not os.path.exists(path):
            return []
        out = []
        with open(path) as fh:
            for line in fh:
                if line.strip():
                    try:
                        out.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue
        return out

    def list_artifacts(self, run_uuid: str, prefix: str = "") -> list[str]:
        root = os.path.join(self.run_dir(run_uuid), prefix)
        if not os.path.isdir(root):
            return []
        out = []
        for dirpath, _, filenames in os.walk(root):
            for name in filenames:
                out.append(os.path.relpath(os.path.join(dirpath, name),
                                           self.run_dir(run_uuid)))
        return sorted(out)

    def artifact_path(self, run_uuid: str, rel: str) -> str:
        from polyaxon_tpu.tracking.events import safe_subpath

        root = os.path.abspath(self.run_dir(run_uuid))
        if os.path.abspath(os.path.join(root, rel)) == root:
            return root  # the run dir itself (artifact listing root)
        return safe_subpath(root, rel)
