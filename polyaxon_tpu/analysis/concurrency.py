"""Concurrency analyzers: the static lock-acquisition graph.

Extracts every ``threading.Lock/RLock/Condition`` the package creates,
tracks which locks are held where (``with`` blocks, including locks
reached transitively through same-module calls), and reports:

- ``lock-order`` — a cycle in the global acquisition graph (AB-BA
  inversion): two code paths that take the same pair of locks in
  opposite orders can deadlock under the right interleaving.
- ``lock-self-deadlock`` — a non-reentrant ``Lock`` nested inside
  itself on one path (guaranteed deadlock, not a race).
- ``lock-blocking-call`` — a lock held across a blocking operation:
  ``time.sleep``/``with_retries`` (sleeps between attempts),
  ``subprocess``, HTTP, fsspec object-store ops, thread joins, and
  control-plane store SCANS (point lookups are exempt — they are O(1)
  by design; scans scale with fleet size and stall every waiter).

Two modeled facts close the gaps AST resolution cannot see:

- calls to the control-plane store's WRITE methods acquire
  ``Store._lock`` (``transition``/``update_run``/``transaction()``...),
  so a thread holding another lock while writing the store gets a
  real graph edge;
- callbacks registered via ``add_transition_listener`` run INSIDE the
  store lock (commit-order delivery), so locks they take — and any
  blocking work they do — are charged against ``Store._lock``.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Optional

from polyaxon_tpu.analysis.core import Finding, SourceFile, register

STORE_PATH = "polyaxon_tpu/controlplane/store.py"
STORE_LOCK_ID = f"{STORE_PATH}::Store._lock"

# Control-plane store methods that take Store._lock (writes + the
# batching context manager). Reads run on per-thread connections.
STORE_WRITE_METHODS = frozenset({
    "transaction", "transition", "update_run", "create_run",
    "add_condition", "create_project", "upsert_queue", "set_quota",
    "delete_queue", "delete_quota", "deoptimize",
})
# Store reads that SCAN (O(fleet)); holding an unrelated lock across
# one stalls that lock for every waiter. Point lookups (get_run,
# last_condition, get_queue, get_quota) are exempt by design.
STORE_SCAN_METHODS = frozenset({
    "list_runs", "scan_runs", "list_run_uuids", "get_runs", "count_runs",
    "find_cached", "list_queues", "list_quotas", "list_projects",
})

LOCK_CTORS = {"Lock": "Lock", "RLock": "RLock", "Condition": "Condition"}

# (dotted-suffix, description) patterns for blocking calls.
_BLOCKING_SUFFIXES = (
    ("time.sleep", "time.sleep"),
    ("subprocess.run", "subprocess"),
    ("subprocess.Popen", "subprocess"),
    ("subprocess.call", "subprocess"),
    ("subprocess.check_call", "subprocess"),
    ("subprocess.check_output", "subprocess"),
    ("urlopen", "HTTP request"),
    ("requests.get", "HTTP request"),
    ("requests.post", "HTTP request"),
    ("socket.create_connection", "socket connect"),
)
_BLOCKING_BARE = {"with_retries": "with_retries (sleeps between attempts)"}
# fsspec / artifact-store ops when called on an `fs`-named receiver.
_FS_METHODS = frozenset({
    "cat_file", "pipe_file", "put", "get", "put_file", "get_file",
    "download_file", "upload_file", "download_dir", "upload_dir",
    "read_bytes", "write_bytes",
})


def _dotted(node: ast.AST) -> str:
    """`a.b.c` for Attribute/Name chains, '' when dynamic."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        return ""
    parts.reverse()
    return ".".join(parts)


@dataclass
class LockDef:
    lock_id: str
    kind: str           # Lock | RLock | Condition
    path: str
    line: int


@dataclass
class FuncInfo:
    qualname: str
    path: str
    acquires: set[str] = field(default_factory=set)
    calls: set[str] = field(default_factory=set)
    blocking: list[tuple[int, str]] = field(default_factory=list)
    # ops performed while holding a lock:
    held_nested: list[tuple[str, str, int]] = field(default_factory=list)
    held_calls: list[tuple[str, str, int]] = field(default_factory=list)
    held_blocking: list[tuple[str, str, int]] = field(default_factory=list)
    self_deadlocks: list[tuple[str, int]] = field(default_factory=list)


@dataclass
class ModuleModel:
    sf: SourceFile
    locks: dict[tuple[str, str], LockDef] = field(default_factory=dict)
    funcs: dict[str, FuncInfo] = field(default_factory=dict)
    listeners: list[str] = field(default_factory=list)  # func keys


def _lock_ctor_kind(value: ast.AST) -> Optional[str]:
    if isinstance(value, ast.Call):
        name = _dotted(value.func)
        if name.startswith("threading."):
            return LOCK_CTORS.get(name.split(".", 1)[1])
        return LOCK_CTORS.get(name) if name in LOCK_CTORS else None
    return None


class _ModuleScanner(ast.NodeVisitor):
    """Pass 1: find every lock definition in the module."""

    def __init__(self, model: ModuleModel):
        self.model = model
        self.class_stack: list[str] = []

    def visit_ClassDef(self, node: ast.ClassDef):
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def _record(self, target: ast.AST, kind: str, line: int):
        cls = self.class_stack[-1] if self.class_stack else ""
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id in ("self", "cls") and cls:
            key = (cls, target.attr)
        elif isinstance(target, ast.Name):
            key = (cls, target.id)
        else:
            return
        qual = f"{key[0]}.{key[1]}" if key[0] else key[1]
        self.model.locks[key] = LockDef(
            lock_id=f"{self.model.sf.path}::{qual}", kind=kind,
            path=self.model.sf.path, line=line)

    def visit_Assign(self, node: ast.Assign):
        kind = _lock_ctor_kind(node.value)
        if kind:
            for target in node.targets:
                self._record(target, kind, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            kind = _lock_ctor_kind(node.value)
            if kind:
                self._record(node.target, kind, node.lineno)
        self.generic_visit(node)


class _FuncScanner(ast.NodeVisitor):
    """Pass 2: per-function lock/call/blocking facts."""

    def __init__(self, model: ModuleModel, info: FuncInfo, cls: str):
        self.model = model
        self.info = info
        self.cls = cls
        self.held: list[str] = []

    # -- resolution helpers -------------------------------------------------
    def _resolve_lock(self, expr: ast.AST) -> Optional[LockDef]:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id in ("self", "cls") and self.cls:
            return self.model.locks.get((self.cls, expr.attr))
        if isinstance(expr, ast.Name):
            return self.model.locks.get(("", expr.id))
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            return self.model.locks.get((expr.value.id, expr.attr))
        return None

    def _store_receiver(self, func: ast.AST) -> Optional[str]:
        """Method name when `func` is a call on a store-shaped receiver
        (`store`, `self.store`, `plane.store`, or `self` inside Store)."""
        if not isinstance(func, ast.Attribute):
            return None
        recv = _dotted(func.value)
        last = recv.rsplit(".", 1)[-1] if recv else ""
        if last == "store" or (recv == "self" and self.cls == "Store"):
            return func.attr
        return None

    def _blocking_desc(self, call: ast.Call) -> Optional[str]:
        name = _dotted(call.func)
        if not name:
            return None
        for suffix, desc in _BLOCKING_SUFFIXES:
            if name == suffix or name.endswith("." + suffix):
                return desc
        bare = name.rsplit(".", 1)[-1]
        if name in _BLOCKING_BARE or bare in _BLOCKING_BARE:
            return _BLOCKING_BARE.get(name) or _BLOCKING_BARE[bare]
        if isinstance(call.func, ast.Attribute):
            recv = _dotted(call.func.value)
            recv_last = recv.rsplit(".", 1)[-1] if recv else ""
            if bare in _FS_METHODS and recv_last in ("fs", "store"):
                return f"object-store op .{bare}()"
            if bare == "join" and "thread" in recv.lower():
                return f"thread join on {recv}"
        method = self._store_receiver(call.func)
        if method in STORE_SCAN_METHODS:
            return f"control-plane store scan .{method}()"
        return None

    def _callee_key(self, call: ast.Call) -> Optional[str]:
        func = call.func
        if isinstance(func, ast.Name):
            return func.id
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name):
            if func.value.id in ("self", "cls") and self.cls:
                return f"{self.cls}.{func.attr}"
            return f"{func.value.id}.{func.attr}"
        return None

    # -- lock bookkeeping ---------------------------------------------------
    def _acquire(self, lock_id: str, kind: str, line: int):
        if lock_id in self.held:
            if kind == "Lock":
                self.info.self_deadlocks.append((lock_id, line))
            return None  # reentrant: no self-edge
        for outer in self.held:
            self.info.held_nested.append((outer, lock_id, line))
        self.info.acquires.add(lock_id)
        self.held.append(lock_id)
        return lock_id

    def visit_With(self, node: ast.With):
        acquired: list[str] = []
        for item in node.items:
            expr = item.context_expr
            lock = self._resolve_lock(expr)
            if lock is not None:
                got = self._acquire(lock.lock_id, lock.kind, node.lineno)
                if got:
                    acquired.append(got)
            elif isinstance(expr, ast.Call):
                method = self._store_receiver(expr.func)
                if method in STORE_WRITE_METHODS:
                    got = self._acquire(STORE_LOCK_ID, "RLock", node.lineno)
                    if got:
                        acquired.append(got)
                self.visit(expr)  # calls inside the context expr
        for stmt in node.body:
            self.visit(stmt)
        for got in reversed(acquired):
            self.held.remove(got)

    def visit_Call(self, node: ast.Call):
        line = node.lineno
        desc = self._blocking_desc(node)
        if desc is not None:
            self.info.blocking.append((line, desc))
            for lock_id in self.held:
                self.info.held_blocking.append((lock_id, desc, line))
        method = self._store_receiver(node.func)
        if method in STORE_WRITE_METHODS:
            # A store write acquires (and releases) Store._lock here.
            if STORE_LOCK_ID not in self.held:
                self.info.acquires.add(STORE_LOCK_ID)
                for outer in self.held:
                    self.info.held_nested.append(
                        (outer, STORE_LOCK_ID, line))
        key = self._callee_key(node)
        if key is not None:
            self.info.calls.add(key)
            for lock_id in self.held:
                self.info.held_calls.append((lock_id, key, line))
        self.generic_visit(node)

    # Nested defs are separate execution contexts (threads/closures run
    # later, not while the enclosing locks are held).
    def visit_FunctionDef(self, node: ast.FunctionDef):
        pass

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        pass

    def visit_Lambda(self, node: ast.Lambda):
        # Lambdas passed to with_retries etc. DO run at the call site;
        # analyze their body in the current held context.
        self.visit(node.body)


def build_model(sf: SourceFile) -> ModuleModel:
    model = ModuleModel(sf=sf)
    _ModuleScanner(model).visit(sf.tree)

    def scan_funcs(body, cls: str, prefix: str):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{node.name}"
                info = FuncInfo(qualname=qual, path=sf.path)
                scanner = _FuncScanner(model, info, cls)
                for stmt in node.body:
                    scanner.visit(stmt)
                model.funcs[qual] = info
                # nested defs become their own entries
                scan_funcs(node.body, cls, f"{qual}.")
            elif isinstance(node, ast.ClassDef):
                scan_funcs(node.body, node.name, f"{node.name}.")

    scan_funcs(sf.tree.body, "", "")

    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "add_transition_listener" and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Attribute) and \
                    isinstance(arg.value, ast.Name) and arg.value.id == "self":
                # registered from inside a class: find which one
                for qual in model.funcs:
                    if qual.endswith("." + arg.attr):
                        model.listeners.append(qual)
    return model


def _propagate(model: ModuleModel) -> tuple[dict[str, set[str]],
                                            dict[str, Optional[str]]]:
    """Same-module transitive closure: which locks may a call to each
    function acquire, and may it block (with an example description)."""
    may_acquire = {q: set(i.acquires) for q, i in model.funcs.items()}
    may_block: dict[str, Optional[str]] = {
        q: (i.blocking[0][1] if i.blocking else None)
        for q, i in model.funcs.items()}
    changed = True
    while changed:
        changed = False
        for qual, info in model.funcs.items():
            for callee in info.calls:
                target = callee if callee in model.funcs else None
                if target is None:
                    continue
                extra = may_acquire[target] - may_acquire[qual]
                if extra:
                    may_acquire[qual] |= extra
                    changed = True
                if may_block[qual] is None and may_block[target] is not None:
                    may_block[qual] = (
                        f"{may_block[target]} via {target}()")
                    changed = True
    return may_acquire, may_block


def _txn_scan_exempt(lock_id: str, desc: str) -> bool:
    """Holding Store._lock across a scan of the SAME store is the
    transaction idiom (a consistent snapshot is the point); the rule
    targets unrelated locks stalled behind O(fleet) reads."""
    return lock_id == STORE_LOCK_ID and "store scan" in desc


@register
def analyze_concurrency(files: list[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    # Global acquisition graph: (outer, inner) -> list of (sf, line, how)
    edges: dict[tuple[str, str], list[tuple[SourceFile, int, str]]] = {}

    def add_edge(outer: str, inner: str, sf: SourceFile, line: int, how: str):
        if outer == inner:
            return
        if sf.suppressed("lock-order", line):
            return
        edges.setdefault((outer, inner), []).append((sf, line, how))

    for sf in files:
        model = build_model(sf)
        may_acquire, may_block = _propagate(model)
        for qual, info in model.funcs.items():
            for lock_id, line in info.self_deadlocks:
                f = sf.finding(
                    "lock-self-deadlock", line,
                    f"non-reentrant Lock {lock_id.split('::')[-1]} "
                    "acquired while already held on this path "
                    "(guaranteed deadlock); use RLock or restructure",
                    qualname=qual)
                if f:
                    findings.append(f)
            for outer, inner, line in info.held_nested:
                add_edge(outer, inner, sf, line, f"nested in {qual}")
            for outer, callee, line in info.held_calls:
                target = callee if callee in model.funcs else None
                if target is None:
                    continue
                for inner in may_acquire[target]:
                    add_edge(outer, inner, sf, line,
                             f"{qual} -> {target}()")
                blocked = may_block[target]
                if blocked is not None and \
                        not _txn_scan_exempt(outer, blocked):
                    f = sf.finding(
                        "lock-blocking-call", line,
                        f"{outer.split('::')[-1]} held across {blocked} "
                        f"(call chain {qual} -> {target}())",
                        qualname=qual)
                    if f:
                        findings.append(f)
            for lock_id, desc, line in info.held_blocking:
                if _txn_scan_exempt(lock_id, desc):
                    continue
                f = sf.finding(
                    "lock-blocking-call", line,
                    f"{lock_id.split('::')[-1]} held across {desc}; "
                    "move the blocking work outside the lock",
                    qualname=qual)
                if f:
                    findings.append(f)
        # Listener callbacks execute under the store lock.
        for qual in model.listeners:
            info = model.funcs.get(qual)
            if info is None:
                continue
            for inner in may_acquire[qual]:
                add_edge(STORE_LOCK_ID, inner, sf,
                         model.sf.tree.body[0].lineno if not info.held_nested
                         else info.held_nested[0][2],
                         f"transition listener {qual} runs under the "
                         "store lock")
            blocked = may_block[qual]
            if blocked is not None:
                first_line = (info.blocking[0][0] if info.blocking
                              else 1)
                f = sf.finding(
                    "lock-blocking-call", first_line,
                    f"Store._lock held across {blocked}: {qual} is a "
                    "transition listener and runs inside the store lock",
                    qualname=qual)
                if f:
                    findings.append(f)

    findings.extend(_cycle_findings(edges))
    return findings


def _cycle_findings(edges: dict[tuple[str, str],
                                list[tuple[SourceFile, int, str]]]
                    ) -> list[Finding]:
    graph: dict[str, set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())

    # Tarjan SCC, iterative.
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    sccs: list[list[str]] = []
    counter = [0]

    def strongconnect(root: str):
        work = [(root, iter(sorted(graph[root])))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(graph[nxt]))))
                    advanced = True
                    break
                elif nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)

    for node in sorted(graph):
        if node not in index:
            strongconnect(node)

    findings = []
    for comp in sccs:
        if len(comp) < 2:
            continue
        members = sorted(comp)
        sites = []
        anchor: Optional[tuple[SourceFile, int]] = None
        for (a, b), occ in sorted(edges.items()):
            if a in comp and b in comp:
                sf, line, how = occ[0]
                if anchor is None:
                    anchor = (sf, line)
                sites.append(f"{a.split('::')[-1]} -> "
                             f"{b.split('::')[-1]} at {sf.path}:{line} "
                             f"({how})")
        assert anchor is not None
        sf, line = anchor
        findings.append(Finding(
            rule="lock-order", path=sf.path, line=line,
            message=("lock-order inversion: cycle through "
                     + ", ".join(m.split("::")[-1] for m in members)
                     + "; edges: " + "; ".join(sites)),
            qualname="", snippet=" -> ".join(members)))
    return findings
